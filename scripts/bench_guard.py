#!/usr/bin/env python3
"""Bench regression guard: diff a bench JSONL run against a baseline.

CI/tooling companion to ``bench.py``: a perf PR must show its wins
WITHOUT regressing the dense-path metrics, and "within tolerance" should
be a command's exit code, not a reviewer eyeballing two JSON blobs.

    python scripts/bench_guard.py BENCH_NEW.jsonl --baseline BENCH_OLD.jsonl
    python scripts/bench_guard.py BENCH_NEW.jsonl --tolerance 0.15 \
        --metric-tolerance http_count_qps=0.3 --require count_intersect_1B_cols_p50
    curl -s localhost:10101/metrics > now.prom
    python scripts/bench_guard.py now.prom --format prom --baseline old.prom \
        --require pilosa_engine_compile_total

Inputs accepted for both sides:
- bench.py output: one JSON object per line, ``{"metric", "value",
  "unit", ...}`` (stderr progress lines are skipped);
- a bench-runner capture like BENCH_r05.json (the JSONL lives in its
  ``tail`` field);
- a snapshot written by ``--write-baseline`` (``{"metrics": {...}}``) —
  the shape BASELINE.json's ``published`` uses;
- ``--format prom`` (or auto-sniffed): a scraped Prometheus ``/metrics``
  exposition — counters/gauges become metrics keyed
  ``name{labels}``, histogram ``_bucket`` series are skipped (their
  ``_sum``/``_count`` pairs carry the comparable signal).  Prom samples
  are dimensionless (direction unknown), so they diff informationally
  and fail only via ``--require``.

Direction is unit-aware: ``us``/``ms``/``s`` regress UP, ``qps``/
``GB/s``/``Mbits/s`` regress DOWN.  Dimensionless telemetry
(``queries/batch``, ``batches``) is reported but never fails the run.
Metrics present in only one file are reported as added/missing;
``--require`` names metrics whose ABSENCE from the new run is itself a
failure (a deleted headline metric must not pass silently).  The
headline metrics in ``AUTO_REQUIRE`` — the north-star latency and the
ingest ``ingest_mbits_s`` throughput — are required automatically
whenever the baseline records them.
"""

from __future__ import annotations

import argparse
import json
import sys

LOWER_BETTER = {"us", "ms", "s", "seconds", "pct"}
HIGHER_BETTER = {"qps", "GB/s", "gbs", "Mbits/s"}

# Headline metrics auto-required whenever the BASELINE carries them: a
# later PR that silently drops the ingest, serving-QPS, or north-star
# line from the bench must fail the guard, not pass by omission
# (equivalent to always passing ``--require ingest_mbits_s`` once a
# baseline records it).  ``http_count_qps``/``http_mixed_qps`` are the
# multi-connection serving headlines (docs/serving.md; bench.py
# --conn-sweep emits the per-connection-count curve around them).
AUTO_REQUIRE = (
    "count_intersect_1B_cols_p50",
    "ingest_mbits_s",
    "http_count_qps",
    "http_mixed_qps",
    # The multichip headline (bench.py --multichip; MULTICHIP_r*.json):
    # required as soon as a baseline records it, so a later round cannot
    # silently drop the multi-device lane.
    "count_intersect_8B_cols_p50",
    # The id-pairs ingest surface (native sparse merge) and the
    # streaming write+read freshness SLO (bench.py --streaming-sweep).
    # Mbits/s regresses DOWN, ms regresses UP — the unit-direction map
    # above already applies; listing them here makes their ABSENCE a
    # failure once a baseline records them (docs/ingest.md).
    "ingest_bits_mbits_s",
    "ingest_freshness_p50_ms",
    # Plan-recording overhead (bench.py --profile-overhead): the query-
    # plan introspection layer is always-on, so its cost is a headline —
    # "pct" regresses UP and the <2% target holds via ABS_CEILING once a
    # baseline records it (docs/observability.md).
    "profile_overhead_pct",
    # The TopN device headline: ROADMAP tracks it trailing the other
    # 1B-col kernels by ~3-4x, but nothing guarded it — a later PR that
    # dropped (or silently regressed) the line must fail here.  "us"
    # regresses UP via the existing unit map.
    "topn_1B_cols_p50",
    # Process-mode serving curve (bench.py --conn-sweep --workers,
    # docs/serving.md "Process mode"): w0 is the in-process reactor
    # oracle, w{1,2,4,8} the worker-process levels.  Required as soon
    # as a baseline records them so the GIL-wall headline cannot be
    # silently dropped; "qps" regresses DOWN.
    "http_count_qps_w0",
    "http_count_qps_w1",
    "http_count_qps_w2",
    "http_count_qps_w4",
    "http_count_qps_w8",
    # Serving-through-failure headlines (bench.py --chaos-sweep,
    # docs/durability.md): query availability while a replica is
    # SIGKILLed mid-load, and the replica-read throughput ratio
    # (any-mode vs primary-mode) on the same cluster.  Required once
    # baselined so a later PR cannot silently drop the chaos lane.
    "availability_under_failure_pct",
    "replica_read_qps_gain",
    # Hinted-handoff headline (bench.py --chaos-sweep, docs/durability.md
    # "Hinted handoff"): the fraction of DESTRUCTIVE writes (Clears on
    # shards the failed node owns) that ack through the degraded steady
    # state.  0 before hinted handoff, 100 with it; ABS_FLOORed at 90 so
    # a regression to the fail-loud policy can never pass as "new
    # metric, no baseline".
    "destructive_write_availability_pct",
    # Partition-heal headline (bench.py --chaos-sweep --fault partition):
    # heal -> cluster NORMAL + hint queues drained + bit-exact
    # convergence; seconds regress UP via the unit map.
    "partition_heal_seconds",
    # Whole-program fusion headlines (bench.py --dashboard-sweep,
    # docs/fusion.md): widget answers/second through the fused N=8
    # mixed drain, its drain-wall p50, and the fused-vs-sequential
    # speedup (ABS_FLOORed below — the ISSUE's >=1.5x acceptance is a
    # standing contract, not a baseline diff).  Required once baselined
    # so the dashboard lane cannot be silently dropped.
    "dashboard_fused_qps",
    "dashboard_p50_ms",
    "dashboard_fused_speedup",
    # Tiered-residency headlines (bench.py --residency-sweep,
    # docs/residency.md): the warm dashboard p50 at 4x oversubscription
    # (ms regress UP), the device-served fraction of the repeated phase
    # (ABS_FLOORed below — the ISSUE 15 >0.5 acceptance is a standing
    # contract), and the promotion worker's overlap throughput.
    # Required once baselined so the bigger-than-HBM lane cannot be
    # silently dropped.
    "oversubscribed_4x_count_p50_ms",
    "residency_hit_rate",
    "promotion_overlap_mbits_s",
    # Predictive block-granular residency headlines (ISSUE 20, same
    # lane): the deep-oversubscription hit rate (ABS_FLOORed at the
    # >0.9 acceptance — the packed 2KiB-block pool must keep the
    # working set resident at 8x), the warm-vs-fully-resident wall
    # ratio at 8x (ABS_CEILINGed at the ~1.2x acceptance), and the
    # equal-budget advisor on/off warm speedup (ABS_FLOORed at 1.0 —
    # promote-ahead must pay for itself).  Required once baselined so
    # the deep-oversubscription phases cannot be silently dropped.
    "residency_hit_rate_8x",
    "oversubscribed_8x_warm_vs_resident",
    "residency_advisor_ab_speedup",
    # Repair-on-write headlines (bench.py --repair-sweep,
    # docs/incremental.md): the memo hit+repair rate of a repeated
    # dashboard under streaming writes (higher-better override +
    # ABS_FLOOR below — the ISSUE 16 >=0.8 acceptance is a standing
    # contract), and the dashboard p50 ratio under ingest vs idle
    # (ABS_CEILINGed at the 1.5x acceptance).  Required once baselined
    # so the streaming-maintenance lane cannot be silently dropped.
    "result_memo_hit_rate_under_write_load",
    "dashboard_p50_under_ingest_vs_idle",
    # Device-resident TopN + cross-index drains (bench.py
    # --dashboard-sweep, docs/fusion.md "TopN on device"): the slab
    # lane's device p50 and executor e2e p50 (ms regress UP, same
    # polarity as topn_1B_cols_p50), the device-trim-vs-host-rank/merge
    # speedup (ABS_FLOORed at the 2x ISSUE 18 acceptance), and the
    # cross-index drain's p50 + fused-vs-sequential speedup.  Required
    # once baselined so the device-TopN lane cannot be silently dropped.
    "topn_device_p50",
    "topn_e2e_p50",
    "topn_device_speedup",
    "dashboard_crossindex_p50_ms",
    "dashboard_crossindex_fused_speedup",
    # Self-hosted metrics history (bench.py --history-overhead,
    # docs/observability.md): the sampler's 1s-interval duty cycle
    # ("pct" regresses UP; the <3% ISSUE 17 acceptance holds via
    # ABS_CEILING) and the 1h-window /debug/history read p50.
    "history_sampler_overhead_pct",
    "history_query_p50_ms",
    # Prefetch-advisor prediction quality + heat-recorder cost
    # (bench.py --advisor-sweep, docs/observability.md "Working-set
    # heat & sequences"): hit rate regresses DOWN (higher-better
    # override + the ISSUE 19 >=0.7 floor below) and the heat
    # recorder's per-query overhead regresses UP (<2% via ABS_CEILING,
    # the profile_overhead_pct methodology).  Required once baselined
    # so the telemetry-substrate lane cannot be silently dropped.
    "prefetch_advisor_hit_rate",
    "heat_overhead_pct",
)

# Direction overrides for metrics whose UNIT would mislead: the unit
# map treats "pct" as lower-better (overhead percentages), but
# availability regresses DOWN; the gain ratio is dimensionless ("x")
# and regresses DOWN too.
NAME_HIGHER_BETTER = {
    "availability_under_failure_pct",
    "destructive_write_availability_pct",
    "replica_read_qps_gain",
    "dashboard_fused_speedup",
    "topn_device_speedup",
    "dashboard_crossindex_fused_speedup",
    "residency_hit_rate",
    "residency_hit_rate_8x",
    "residency_advisor_ab_speedup",
    "result_memo_hit_rate_under_write_load",
    "prefetch_advisor_hit_rate",
}

# Built-in per-metric tolerance (used when no --metric-tolerance names
# the metric): profile_overhead_pct's denominator is a wall p50 subject
# to this container's transport jitter, so the ratio wobbles ~2x run to
# run while the binding contract is the absolute <2% ceiling below.
DEFAULT_METRIC_TOL = {
    "profile_overhead_pct": 1.0,
    # Tick cost over a fixed interval: the numerator is a best-of-K
    # microbench on shared vCPUs, so the ratio wobbles while the
    # binding contract is the absolute <3% ceiling below.
    "history_sampler_overhead_pct": 1.0,
    # A ratio of two closed-loop QPS measurements on a contended host:
    # wobbles far more than either numerator; the availability floor
    # below is the binding chaos contract.
    "replica_read_qps_gain": 0.5,
    # Same shape: fused/sequential wall ratio on shared vCPUs; the 1.5x
    # ABS_FLOOR below is the binding fusion contract.
    "dashboard_fused_speedup": 0.5,
    # Same shape again (PR 18): slab-vs-host and cross-index wall
    # ratios; the 2x ABS_FLOOR below is the binding slab contract.
    "topn_device_speedup": 0.5,
    "dashboard_crossindex_fused_speedup": 0.5,
    # Two wall-p50 ratios on shared vCPUs (repair sweep): the absolute
    # floor/ceiling below carry the binding ISSUE 16 contracts.
    "result_memo_hit_rate_under_write_load": 0.5,
    "dashboard_p50_under_ingest_vs_idle": 0.5,
    # Replay-estimator-over-wall-p50 ratio (same shape as
    # profile_overhead_pct); the absolute <2% ceiling below binds.
    "heat_overhead_pct": 1.0,
    # Wall ratios on shared vCPUs (ISSUE 20): the absolute bounds below
    # carry the binding deep-oversubscription contracts.
    "oversubscribed_8x_warm_vs_resident": 0.5,
    "residency_advisor_ab_speedup": 0.5,
}

# Absolute ceilings enforced regardless of the baseline value: crossing
# one is a failure even when the relative delta is within tolerance.
ABS_CEILING = {
    "profile_overhead_pct": 2.0,
    # ISSUE 17 acceptance: the history sampler's worst-case duty cycle
    # at the 1s smoke interval stays under 3% of one core.
    "history_sampler_overhead_pct": 3.0,
    # ISSUE 16 acceptance: a repeated dashboard under streaming ingest
    # stays within 1.5x of its idle p50 (repair keeps serves O(changed
    # bits) instead of O(data) recomputes).
    "dashboard_p50_under_ingest_vs_idle": 1.5,
    # ISSUE 19 acceptance: the heat recorder's per-query cost (heat
    # tables + miner transition + advisor grade/learn/advise) stays
    # under 2% of the query wall p50.
    "heat_overhead_pct": 2.0,
    # ISSUE 20 acceptance: warm dashboard p50 at 8x oversubscription
    # stays within ~1.2x of the fully-resident engine (the block pool
    # serves the working set from device, not host fallback).
    "oversubscribed_8x_warm_vs_resident": 1.2,
}

# Absolute floors, the ceiling's dual: availability under failure below
# this is a failure no matter what the baseline recorded (with replica
# hedging, reads through a replica kill must stay near-continuous), and
# the fused N=8 dashboard drain must beat the sequential per-query path
# by >=1.5x (the whole-program fusion acceptance, docs/fusion.md).
ABS_FLOOR = {
    "availability_under_failure_pct": 90.0,
    "destructive_write_availability_pct": 90.0,
    "dashboard_fused_speedup": 1.5,
    # ISSUE 18 acceptance: the executor TopN e2e with device trim beats
    # the in-run host rank/merge oracle by >=2x.
    "topn_device_speedup": 2.0,
    # The ISSUE 15 acceptance: >0.5 of the repeated-dashboard phase
    # must serve from device residency at 4x oversubscription.
    "residency_hit_rate": 0.5,
    # ISSUE 16 acceptance, tightened by ISSUE 20: with clear_row and
    # set_row instrumented (only load_row_words stays opaque), the
    # dashboard answers >=0.9 of its queries from the memo or an
    # O(changed-bits) repair under write load.
    "result_memo_hit_rate_under_write_load": 0.9,
    # ISSUE 20 acceptance: >0.9 of the repeated-dashboard phase serves
    # from the packed block pool at 8x oversubscription.
    "residency_hit_rate_8x": 0.9,
    # ISSUE 20 acceptance: at equal budget, advisor-on warm p50 beats
    # advisor-off (promote-ahead lands the next dashboard's stacks
    # before its queries arrive).
    "residency_advisor_ab_speedup": 1.0,
    # ISSUE 19 acceptance: on the alternating two-dashboard replay the
    # advisor's advised rows hit >=0.7 of the rows the next query
    # actually touched.
    "prefetch_advisor_hit_rate": 0.7,
}


def parse_jsonl(text: str) -> dict:
    """{metric: record} from bench JSONL text (non-metric lines skipped)."""
    out = {}
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "metric" in rec and "value" in rec:
            out[rec["metric"]] = rec
    return out


def parse_prometheus(text: str) -> dict:
    """{``name{labels}``: record} from a Prometheus text exposition.
    Histogram ``_bucket`` series are skipped (hundreds of per-le lines
    whose signal the ``_sum``/``_count`` pair already carries).  Prom
    samples carry no unit, so records are dimensionless: the diff is
    informational and only ``--require`` can fail the run."""
    out = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name_labels, sep, value = line.rpartition(" ")
        if not sep:
            continue
        base = name_labels.split("{", 1)[0]
        if base.endswith("_bucket"):
            continue
        try:
            v = float(value)
        except ValueError:
            continue
        out[name_labels] = {"metric": name_labels, "value": v, "unit": ""}
    return out


def _sniff_prom(text: str) -> bool:
    head = text.lstrip()[:256]
    return head.startswith("# HELP") or head.startswith("# TYPE")


def load_metrics(path: str, fmt: str = "auto") -> dict:
    with open(path) as f:
        text = f.read()
    if fmt == "prom" or (fmt == "auto" and _sniff_prom(text)):
        return parse_prometheus(text)
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        doc = None
    if isinstance(doc, dict):
        if isinstance(doc.get("metrics"), dict):  # --write-baseline shape
            return {
                k: v for k, v in doc["metrics"].items()
                if isinstance(v, dict) and "value" in v
            }
        if isinstance(doc.get("published"), dict) and doc["published"]:
            return {
                k: v for k, v in doc["published"].items()
                if isinstance(v, dict) and "value" in v
            }
        if isinstance(doc.get("tail"), str):  # bench-runner capture
            return parse_jsonl(doc["tail"])
        if "metric" in doc and "value" in doc:  # single-record file
            return {doc["metric"]: doc}
    return parse_jsonl(text)


def check(current: dict, baseline: dict, tolerance: float,
          per_metric: dict, require=()) -> tuple:
    """(failures, notes, checked): tolerance violations, informational
    lines, and how many metrics were actually compared."""
    require = tuple(require) + tuple(
        n for n in AUTO_REQUIRE if n in baseline and n not in require
    )
    failures, notes, checked = [], [], 0
    for name in sorted(baseline):
        base = baseline[name]
        cur = current.get(name)
        bv = base.get("value")
        if not isinstance(bv, (int, float)) or bv <= 0:
            continue
        if cur is None:
            (failures if name in require else notes).append(
                f"{name}: missing from the new run (baseline {bv})"
            )
            continue
        cv = float(cur["value"])
        unit = str(base.get("unit", ""))
        tol = per_metric.get(name, DEFAULT_METRIC_TOL.get(name, tolerance))
        checked += 1
        delta = cv / float(bv) - 1.0
        line = f"{name}: {cv:g} vs {bv:g} {unit} ({delta:+.1%}, tol {tol:.0%})"
        ceiling = ABS_CEILING.get(name)
        floor = ABS_FLOOR.get(name)
        higher = name in NAME_HIGHER_BETTER or unit in HIGHER_BETTER
        lower = unit in LOWER_BETTER and name not in NAME_HIGHER_BETTER
        if ceiling is not None and cv > ceiling:
            failures.append(f"{name}: {cv:g} exceeds the absolute "
                            f"ceiling {ceiling:g} {unit}")
        elif floor is not None and cv < floor:
            failures.append(f"{name}: {cv:g} below the absolute "
                            f"floor {floor:g} {unit}")
        elif lower and delta > tol:
            failures.append(line)
        elif higher and -delta > tol:
            failures.append(line)
        else:
            notes.append("ok " + line)
    for name in sorted(set(current) - set(baseline)):
        notes.append(f"{name}: new metric (no baseline)")
        # Absolute bounds apply even on a metric's FIRST appearance —
        # a floor/ceiling is a standing contract, not a baseline diff.
        cv = current[name].get("value")
        if not isinstance(cv, (int, float)):
            continue
        unit = str(current[name].get("unit", ""))
        ceiling = ABS_CEILING.get(name)
        floor = ABS_FLOOR.get(name)
        if ceiling is not None and cv > ceiling:
            failures.append(f"{name}: {cv:g} exceeds the absolute "
                            f"ceiling {ceiling:g} {unit}")
        elif floor is not None and cv < floor:
            failures.append(f"{name}: {cv:g} below the absolute "
                            f"floor {floor:g} {unit}")
    for name in require:
        if name not in current:
            failures.append(f"{name}: required metric missing from the new run")
    # The multichip headline carries its shape (cols, n_devices): a
    # round that shrinks either would read as a spurious speedup under
    # the latency-only diff, so a shrink is itself a regression.
    head = "count_intersect_8B_cols_p50"
    base_h, cur_h = baseline.get(head), current.get(head)
    if base_h and cur_h:
        for fld in ("cols", "n_devices"):
            bv, cv = base_h.get(fld), cur_h.get(fld)
            if bv and cv and cv < bv:
                failures.append(
                    f"{head}: {fld} shrank to {cv} (baseline {bv}) — a "
                    "smaller shape must not pass as a latency win"
                )
    return failures, notes, checked


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("current", help="new bench JSONL (or runner capture)")
    ap.add_argument(
        "--baseline", default="BASELINE.json",
        help="baseline file (bench JSONL, runner capture, or snapshot; "
        "default: BASELINE.json)",
    )
    ap.add_argument(
        "--tolerance", type=float, default=0.15,
        help="default relative regression tolerance (default 0.15)",
    )
    ap.add_argument(
        "--metric-tolerance", action="append", default=[],
        metavar="NAME=TOL", help="per-metric tolerance override",
    )
    ap.add_argument(
        "--require", action="append", default=[], metavar="NAME",
        help="metric that MUST appear in the new run",
    )
    ap.add_argument(
        "--write-baseline", metavar="PATH",
        help="also snapshot the new run's metrics to PATH",
    )
    ap.add_argument(
        "--format", choices=("auto", "jsonl", "prom"), default="auto",
        help="input format for BOTH files: bench JSONL, a Prometheus "
        "/metrics snapshot, or auto-sniffed per file (default)",
    )
    ap.add_argument("--quiet", action="store_true", help="failures only")
    args = ap.parse_args(argv)

    per_metric = {}
    for spec in args.metric_tolerance:
        name, sep, tol = spec.partition("=")
        try:
            if not sep:
                raise ValueError
            per_metric[name] = float(tol)
        except ValueError:
            ap.error(
                f"--metric-tolerance expects NAME=FLOAT, got {spec!r}"
            )

    current = load_metrics(args.current, args.format)
    baseline = load_metrics(args.baseline, args.format)
    failures, notes, checked = check(
        current, baseline, args.tolerance, per_metric, tuple(args.require)
    )
    if args.write_baseline:
        with open(args.write_baseline, "w") as f:
            json.dump({"metrics": current}, f, indent=2, sort_keys=True)
    if not args.quiet:
        for line in notes:
            print(line)
    for line in failures:
        print("REGRESSION " + line, file=sys.stderr)
    print(
        f"bench_guard: {checked} compared, {len(failures)} regressions",
        file=sys.stderr,
    )
    if not baseline:
        print(
            "bench_guard: baseline has no metrics — nothing enforced",
            file=sys.stderr,
        )
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
