"""Kernel-bandwidth experiments on the real chip (r4 VERDICT weak #2-4).

Measures the three below-stream kernels at bench shapes and candidate
restructurings, with device-trace timing (same method as bench.py).
Findings drive kernels.py/bsi.py; this script is the decision record.

Run: JAX_PLATFORMS=axon python scripts/kernel_opt.py
"""

import functools
import glob
import gzip
import json
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from pilosa_tpu.ops import bsi as bsi_ops
from pilosa_tpu.parallel import kernels
from pilosa_tpu.parallel.mesh import SHARD_AXIS

S, W = 960, 32768
DEPTH = 8
HBM = 755.8  # measured read ceiling GB/s


def device_ms(fn, reps=12):
    jax.block_until_ready(fn(0))
    d = tempfile.mkdtemp(prefix="kopt_")
    try:
        jax.profiler.start_trace(d)
        try:
            jax.block_until_ready([fn(i) for i in range(reps)])
        finally:
            jax.profiler.stop_trace()
        out = {}
        for path in glob.glob(d + "/plugins/profile/*/*.trace.json.gz"):
            doc = json.load(gzip.open(path, "rt"))
            evs = doc.get("traceEvents", [])
            pids = {
                e["pid"]: e.get("args", {}).get("name", "")
                for e in evs
                if e.get("ph") == "M" and e.get("name") == "process_name"
            }
            for e in evs:
                if e.get("ph") != "X" or "TPU" not in pids.get(e.get("pid"), ""):
                    continue
                if not e.get("name", "").startswith("jit_"):
                    continue
                out.setdefault(e["name"], []).append(e.get("dur", 0))
        if not out:
            return None
        durs = sorted(max(out.values(), key=sum))
        return durs[len(durs) // 2] / 1e3
    finally:
        shutil.rmtree(d, ignore_errors=True)


def report(name, ms, gb):
    gbs = gb / (ms / 1e3)
    print(f"{name:34s} {ms:8.3f} ms  {gbs:7.1f} GB/s  ({gbs / HBM * 100:4.0f}% of stream)")
    return gbs


mesh = Mesh(np.array(jax.devices()[:1]), (SHARD_AXIS,))
rng = np.random.default_rng(7)

print("building operands...")
planes = jnp.asarray(
    np.concatenate(
        [
            rng.integers(0, 1 << 32, size=(DEPTH, S, W), dtype=np.uint32),
            np.full((1, S, W), 0xFFFFFFFF, dtype=np.uint32),
        ]
    )
)
mask = jnp.asarray(np.full((S, 1), 0xFFFFFFFF, dtype=np.uint32))
cands = jnp.asarray(
    rng.integers(0, 1 << 32, size=(16, S, W), dtype=np.uint32)
    & rng.integers(0, 1 << 32, size=(16, S, W), dtype=np.uint32)
)
src = jnp.asarray(rng.integers(0, 1 << 32, size=(S, W), dtype=np.uint32))
ga = jnp.asarray(rng.integers(0, 1 << 32, size=(4, S, W), dtype=np.uint32))
gb_ = jnp.asarray(rng.integers(0, 1 << 32, size=(2, S, W), dtype=np.uint32))
gc = jnp.asarray(rng.integers(0, 1 << 32, size=(2, S, W), dtype=np.uint32))
cnt = jnp.asarray(rng.integers(0, 1000, size=(16, S), dtype=np.int32))
thr = jnp.int32(1)
jax.block_until_ready((planes, cands, src, ga, gb_, gc))

GB_MM = planes.nbytes / 1e9
GB_TOP = (cands.nbytes + src.nbytes) / 1e9
GB_G3 = (ga.nbytes + gb_.nbytes + gc.nbytes) / 1e9

_pc = lambda x: jax.lax.population_count(x).astype(jnp.int32)

# ---------------- min/max --------------------------------------------------
print(f"\n== BSI min ({GB_MM:.2f} GB nominal) ==")

pspec = ("slice", 0, DEPTH + 1)


def mm_current(i):
    return kernels.minmax_tree(
        mesh, ("ones",), (), pspec, True, mask, planes
    )


report("minmax current (vmap word-local)", device_ms(mm_current), GB_MM)


@functools.partial(jax.jit, static_argnums=(0,))
def mm_v2(mesh, mask, pm):
    """depth<=31: single uint32 accumulator, no vmap, fused reductions."""

    def body(m, p):
        depth = p.shape[0] - 1
        keep0 = p[depth] & jnp.broadcast_to(m, p.shape[1:])
        keep = keep0
        lo = jnp.zeros(keep.shape, jnp.uint32)
        for i in range(depth - 1, -1, -1):
            zeros = keep & ~p[i]
            has0 = zeros != 0
            keep = jnp.where(has0, zeros, keep)
            lo = lo | jnp.where(has0, jnp.uint32(0), jnp.uint32(1 << i))
        valid = keep0 != 0
        full = jnp.uint32(0xFFFFFFFF)
        min_lo = jnp.min(jnp.where(valid, lo, full), axis=1)  # [S]
        attain = valid & (lo == min_lo[:, None])
        count = jnp.sum(jnp.where(attain, _pc(keep), 0), axis=1)
        return (
            jax.lax.psum(min_lo * 0, SHARD_AXIS) + min_lo,
            jax.lax.psum(count * 0, SHARD_AXIS) + count,
        )

    return shard_map(
        body, mesh=mesh, in_specs=(P(SHARD_AXIS), P(None, SHARD_AXIS)),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
    )(mask, pm)


report("minmax v2 (no-vmap single-acc)", device_ms(lambda i: mm_v2(mesh, mask, planes)), GB_MM)


@functools.partial(jax.jit, static_argnums=(0,))
def mm_v3(mesh, mask, pm):
    """Two-kernel: min via walk only (no count), then count in 2nd pass
    reading planes again is silly — instead derive count from lo alone:
    count = popcount of keep where lo == min; keep recomputable from
    attain columns... here: fuse min+count but compute per-shard min
    via a segmented reshape reduction (words-major blocks)."""

    def body(m, p):
        depth = p.shape[0] - 1
        keep0 = p[depth] & jnp.broadcast_to(m, p.shape[1:])
        keep = keep0
        lo = jnp.zeros(keep.shape, jnp.uint32)
        for i in range(depth - 1, -1, -1):
            zeros = keep & ~p[i]
            has0 = zeros != 0
            keep = jnp.where(has0, zeros, keep)
            lo = lo | jnp.where(has0, jnp.uint32(0), jnp.uint32(1 << i))
        valid = keep0 != 0
        full = jnp.uint32(0xFFFFFFFF)
        lo_v = jnp.where(valid, lo, full)
        # one pass: min and argmin-ish count folded via two reductions
        # XLA sibling-fuses these (same inputs).
        min_lo = jnp.min(lo_v, axis=1)
        count = jnp.sum(
            jnp.where(lo_v == min_lo[:, None], _pc(keep), 0), axis=1
        )
        return min_lo, count

    return shard_map(
        body, mesh=mesh, in_specs=(P(SHARD_AXIS), P(None, SHARD_AXIS)),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
    )(mask, pm)


report("minmax v3 (sibling reduce)", device_ms(lambda i: mm_v3(mesh, mask, planes)), GB_MM)

# ---------------- TopN scoring --------------------------------------------
print(f"\n== TopN full ({GB_TOP:.2f} GB nominal) ==")


def top_current(i):
    return kernels.topn_full_tree(
        mesh, ("ones",), (), 5, tuple(range(15, -1, -1)), mask, cands, cnt, thr
    )


report("topn current", device_ms(top_current), GB_TOP)


@functools.partial(jax.jit, static_argnums=(0, 1))
def top_v2(mesh, n_out, mask, cmat, cn, th):
    """Chunked scan over the word axis: each step loads src chunk once
    and scores ALL K candidates against it from VMEM."""

    def body(m, cmat, cn, th):
        K = cmat.shape[0]
        src_ = jnp.broadcast_to(m, cmat.shape[1:])
        TW = 4096
        nW = W // TW
        # [K, S, nW, TW] -> scan over nW
        cm = cmat.reshape(K, S, nW, TW).transpose(2, 0, 1, 3)
        sr = src_.reshape(S, nW, TW).transpose(1, 0, 2)

        def step(acc, xs):
            cchunk, schunk = xs
            acc = acc + jnp.sum(
                _pc(cchunk & schunk[None, :, :]), axis=-1
            )
            return acc, None

        scores, _ = jax.lax.scan(
            step,
            jax.lax.pvary(jnp.zeros((K, S), jnp.int32), (SHARD_AXIS,)),
            (cm, sr),
        )
        gate = jnp.logical_and(cn >= th, scores >= th)
        totals = jax.lax.psum(
            jnp.sum(jnp.where(gate, scores, 0), axis=1), SHARD_AXIS
        )
        vals, idx = jax.lax.top_k(totals, n_out)
        return (vals, idx)

    return shard_map(
        body, mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(None, SHARD_AXIS), P(None, SHARD_AXIS), P()),
        out_specs=(P(), P()),
    )(mask, cmat, cn, th)


# gather-free identity candidates == full reverse in current; use src=ones
report("topn v2 (word-chunk scan)", device_ms(lambda i: top_v2(mesh, 5, src, cands, cnt, thr)), GB_TOP)


@functools.partial(jax.jit, static_argnums=(0, 1))
def top_v3(mesh, n_out, mask, cmat, cn, th):
    """Flat X-axis chunking (S folded into the chunk axis)."""

    def body(m, cmat, cn, th):
        K = cmat.shape[0]
        src_ = jnp.broadcast_to(m, cmat.shape[1:])
        X = S * W
        C = 1 << 21  # 2M words: 8 MB src chunk + K x 8 MB cand rows? no - K*C*4
        nC = X // C
        cm = cmat.reshape(K, nC, C).transpose(1, 0, 2)
        sr = src_.reshape(nC, C)

        def step(acc, xs):
            cchunk, schunk = xs
            return acc + jnp.sum(_pc(cchunk & schunk[None, :]), axis=-1), None

        flat, _ = jax.lax.scan(
            step,
            jax.lax.pvary(jnp.zeros((K,), jnp.int32), (SHARD_AXIS,)),
            (cm, sr),
        )
        # NOTE: loses per-shard gating - measures bandwidth shape only.
        totals = jax.lax.psum(flat, SHARD_AXIS)
        vals, idx = jax.lax.top_k(totals, n_out)
        return (vals, idx)

    return shard_map(
        body, mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(None, SHARD_AXIS), P(None, SHARD_AXIS), P()),
        out_specs=(P(), P()),
    )(mask, cmat, cn, th)


report("topn v3 (flat-chunk, no gate)", device_ms(lambda i: top_v3(mesh, 5, src, cands, cnt, thr)), GB_TOP)

# ---------------- 3-field GroupBy ------------------------------------------
print(f"\n== GroupBy 3-field ({GB_G3:.2f} GB nominal) ==")


def g3_current(i):
    return kernels.groupn_tree(
        mesh, ("ones",), (),
        (tuple(range(4)), tuple(range(2)), tuple(range(2))),
        mask, ga, gb_, gc,
    )


report("groupn current (broadcast)", device_ms(g3_current), GB_G3)


@functools.partial(jax.jit, static_argnums=(0,))
def g3_v2(mesh, mask, a, b, c):
    """Word-chunk scan: per chunk, all 16 combos from VMEM-resident
    chunk loads."""

    def body(m, a, b, c):
        TW = 4096
        nW = W // TW
        at = a.reshape(4, S, nW, TW).transpose(2, 0, 1, 3)
        bt = b.reshape(2, S, nW, TW).transpose(2, 0, 1, 3)
        ct = c.reshape(2, S, nW, TW).transpose(2, 0, 1, 3)

        def step(acc, xs):
            ac, bc, cc = xs
            inter = (
                ac[:, None, None]
                & bc[None, :, None]
                & cc[None, None, :]
            )  # [4,2,2,S,TW]
            return acc + jnp.sum(_pc(inter), axis=(-2, -1)), None

        counts, _ = jax.lax.scan(
            step,
            jax.lax.pvary(jnp.zeros((4, 2, 2), jnp.int32), (SHARD_AXIS,)),
            (at, bt, ct),
        )
        return jax.lax.psum(counts, SHARD_AXIS)

    return shard_map(
        body, mesh=mesh,
        in_specs=(P(SHARD_AXIS),) + (P(None, SHARD_AXIS),) * 3,
        out_specs=P(),
    )(mask, a, b, c)


report("groupn v2 (word-chunk scan)", device_ms(lambda i: g3_v2(mesh, mask, ga, gb_, gc)), GB_G3)


@functools.partial(jax.jit, static_argnums=(0,))
def g3_v3(mesh, mask, a, b, c):
    """Pairwise staging: ab = a&b materialized once ([8,S,W] write),
    then ab&c reduce - trades an 8-plane write+read for the re-reads."""

    def body(m, a, b, c):
        ab = a[:, None] & b[None, :]  # [4,2,S,W]
        inter = ab[:, :, None] & c[None, None, :]
        return jax.lax.psum(
            jnp.sum(_pc(inter), axis=(-2, -1)), SHARD_AXIS
        )

    return shard_map(
        body, mesh=mesh,
        in_specs=(P(SHARD_AXIS),) + (P(None, SHARD_AXIS),) * 3,
        out_specs=P(),
    )(mask, a, b, c)


report("groupn v3 (pairwise stage)", device_ms(lambda i: g3_v3(mesh, mask, ga, gb_, gc)), GB_G3)

print("\ndone")
